"""Fleet event trace plane: gating, ring/window semantics, the file sink
(rotation + torn-tail tolerance), /debug/trace over a real socket, and the
flight dump's trace tail (observability/trace.py, docs/simulation.md).

Same cost bargain as test_observability_plane.py: the live-engine test
rides the deterministic FakeCore (pure numpy, no compile) so the module
exercises the REAL scheduler driver thread and real aiohttp sockets while
staying seconds-cheap.
"""

import json
import os
import time

import pytest
import requests

from test_scheduler_fuzz import FakeCore

from generativeaiexamples_tpu.engine.scheduler import Scheduler
from generativeaiexamples_tpu.engine.server import ModelServer
from generativeaiexamples_tpu.engine.tokenizer import ByteTokenizer
from generativeaiexamples_tpu.observability.trace import (
    EventTrace, TRACE, read_jsonl)


@pytest.fixture
def clean_trace():
    """Arm the process-global TRACE for a test and restore it after —
    other modules rely on the default-off state."""
    prev = (TRACE.enabled, TRACE.path, TRACE.capacity)
    TRACE.configure(mode="on", path="")
    TRACE.reset()
    yield TRACE
    TRACE.configure(mode="on" if prev[0] else "off",
                    path=prev[1] or "", capacity=prev[2])
    TRACE.reset()


# ------------------------------------------------------------- gating

def test_default_off_records_nothing():
    t = EventTrace()          # fresh instance, env APP_TRACE unset
    assert t.enabled is False
    t.emit("submit", rid="r1")
    assert t.records() == []
    assert t.describe()["recorded_total"] == 0
    assert t.describe()["mode"] == "off"


def test_emit_window_and_kind_filter(clean_trace):
    t = clean_trace
    for i in range(6):
        t.emit("submit" if i % 2 == 0 else "finish", rid=f"r{i}")
    recs = t.records()
    assert len(recs) == 6
    assert [r["seq"] for r in recs] == list(range(6))
    assert all(r["v"] == 1 and "mono" in r for r in recs)
    only_fin = t.window(3600.0, kinds=("finish",))
    assert {r["kind"] for r in only_fin} == {"finish"}
    assert len(only_fin) == 3
    assert t.window(3600.0, limit=2) == recs[-2:]
    # a window in the past excludes everything
    assert t.window(0.0) == [] or all(
        r["mono"] >= recs[-1]["mono"] for r in t.window(0.0))


def test_ring_bounded_and_capacity_floor(clean_trace):
    t = clean_trace
    t.configure(capacity=256)          # floor: configure clamps up to 256
    for i in range(300):
        t.emit("qos", i=i)
    d = t.describe()
    assert d["buffered"] == 256
    assert d["recorded_total"] == 300
    assert d["dropped"] == 44
    assert t.records()[0]["i"] == 44   # oldest evicted first


# ------------------------------------------------------------- file sink

def test_sink_flush_dump_and_reload(tmp_path, clean_trace):
    t = clean_trace
    sink = str(tmp_path / "trace.jsonl")
    t.configure(path=sink)
    for i in range(10):
        t.emit("dispatch", step=i)
    t.flush()
    on_disk = read_jsonl(sink)
    assert [r["step"] for r in on_disk] == list(range(10))
    # ring dump produces the same line shape
    dump = str(tmp_path / "dump.jsonl")
    n = t.dump_jsonl(dump)
    assert n == 10
    assert read_jsonl(dump) == t.records()


def test_sink_rotation(tmp_path, clean_trace):
    t = clean_trace
    sink = str(tmp_path / "trace.jsonl")
    t.configure(path=sink)
    t.rotate_bytes = 2048              # tiny budget to force rotation
    for i in range(400):
        t.emit("dispatch", step=i, pad="x" * 40)
    t.flush()
    assert os.path.exists(sink + ".1")          # rotated predecessor
    assert os.path.getsize(sink) <= 2048 + 120 * 128   # bounded post-rotate
    # both generations still parse
    read_jsonl(sink + ".1")
    read_jsonl(sink)


def test_read_jsonl_tolerates_torn_tail_only(tmp_path):
    p = str(tmp_path / "t.jsonl")
    with open(p, "w", encoding="utf-8") as f:
        f.write(json.dumps({"v": 1, "kind": "submit", "seq": 0}) + "\n")
        f.write('{"v": 1, "kind": "fin')        # killed mid-write
    recs = read_jsonl(p)
    assert len(recs) == 1
    # torn line NOT at the tail = not a trace file → loud
    with open(p, "w", encoding="utf-8") as f:
        f.write("not json at all\n")
        f.write(json.dumps({"v": 1}) + "\n")
    with pytest.raises(ValueError, match="undecodable"):
        read_jsonl(p)


# ------------------------------------------------- live engine over HTTP

from test_chain_server import _ServerThread, _free_port  # noqa: E402


@pytest.fixture
def served_engine(clean_trace):
    core = FakeCore(batch=4, max_seq=64, page_size=8, chunk=16, steps=2,
                    group=4)
    sched = Scheduler(core, ByteTokenizer())
    sched.start()
    port = _free_port()
    server = _ServerThread(ModelServer(sched, "fake-tpu").app, port)
    server.start()
    try:
        yield f"http://127.0.0.1:{port}"
    finally:
        server.stop()
        sched.stop()


def test_debug_trace_endpoint_live(served_engine):
    r = requests.post(f"{served_engine}/v1/completions",
                      json={"prompt": "trace me", "max_tokens": 6},
                      timeout=30)
    assert r.status_code == 200
    body = requests.get(f"{served_engine}/debug/trace?window=600",
                        timeout=5).json()
    assert body["enabled"] is True
    kinds = {rec["kind"] for rec in body["records"]}
    assert {"submit", "admit", "dispatch", "finish"} <= kinds
    fin = [rec for rec in body["records"] if rec["kind"] == "finish"]
    assert fin and fin[-1]["completion_tokens"] > 0
    # kind filter + limit are honored
    only = requests.get(
        f"{served_engine}/debug/trace?window=600&kind=submit&limit=1",
        timeout=5).json()
    assert len(only["records"]) == 1
    assert only["records"][0]["kind"] == "submit"
    # bad window is a 400, not a 500
    assert requests.get(f"{served_engine}/debug/trace?window=x",
                        timeout=5).status_code == 400


def test_debug_trace_endpoint_off_mode(served_engine):
    TRACE.configure(mode="off")
    try:
        body = requests.get(f"{served_engine}/debug/trace", timeout=5).json()
        assert body["enabled"] is False
        assert "hint" in body and "APP_TRACE" in body["hint"]
        assert "records" not in body          # no empty-list masquerade
    finally:
        TRACE.configure(mode="on")


def test_flight_dump_embeds_trace_tail(tmp_path, served_engine):
    from generativeaiexamples_tpu.observability.flight import FLIGHT
    requests.post(f"{served_engine}/v1/completions",
                  json={"prompt": "dump me", "max_tokens": 4}, timeout=30)
    out = FLIGHT.dump(str(tmp_path / "flight.json"))
    with open(out, "r", encoding="utf-8") as f:
        payload = json.load(f)
    tr = payload["trace"]
    assert tr["enabled"] is True
    assert tr["schema_version"] == 1
    assert any(rec["kind"] == "finish" for rec in tr["tail"])
