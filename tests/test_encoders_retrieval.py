"""Encoders (embed/rerank, HF BERT parity) + retrieval (store/IVF/BM25/splitter)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from generativeaiexamples_tpu.encoders import Embedder, Reranker
from generativeaiexamples_tpu.models import bert
from generativeaiexamples_tpu.retrieval import BM25Index, Document, TokenTextSplitter, VectorStore
from generativeaiexamples_tpu.retrieval.bm25 import reciprocal_rank_fusion


# ----------------------------------------------------------------- bert/hf

def test_bert_hf_parity():
    torch = pytest.importorskip("torch")
    from transformers import BertConfig as HFConfig, BertModel

    hf_cfg = HFConfig(vocab_size=120, hidden_size=32, num_hidden_layers=2,
                      num_attention_heads=2, intermediate_size=64,
                      max_position_embeddings=64, type_vocab_size=2,
                      layer_norm_eps=1e-12, hidden_act="gelu")
    torch.manual_seed(0)
    hf = BertModel(hf_cfg).eval()
    cfg = bert.BertConfig(vocab_size=120, dim=32, n_layers=2, n_heads=2,
                          hidden_dim=64, max_positions=64)
    params = bert.params_from_hf(hf.state_dict(), cfg)

    ids = np.array([[2, 5, 9, 14, 77, 3]], dtype=np.int64)
    mask = np.array([[1, 1, 1, 1, 0, 0]], dtype=np.int64)
    with torch.no_grad():
        hf_out = hf(torch.tensor(ids),
                    attention_mask=torch.tensor(mask)).last_hidden_state.numpy()
    ours = np.asarray(bert.encode(params, cfg, jnp.asarray(ids, jnp.int32),
                                  jnp.asarray(mask, bool)))
    # HF computes positions for padded slots too; compare valid positions
    np.testing.assert_allclose(ours[:, :4], hf_out[:, :4], atol=2e-4, rtol=2e-3)


def test_embedder_shapes_and_normalization():
    e = Embedder()
    vecs = e.embed_documents(["short", "a slightly longer passage of text",
                              "third"])
    assert vecs.shape == (3, e.dim)
    np.testing.assert_allclose(np.linalg.norm(vecs, axis=1), 1.0, atol=1e-5)
    # query/passage prefixes must differ
    q = e.embed_queries(["short"])
    assert not np.allclose(q[0], vecs[0])


def test_embedder_batching_consistency():
    e = Embedder(max_batch=2)
    texts = [f"text number {i}" for i in range(5)]
    batched = e.embed_documents(texts)
    single = np.concatenate([e.embed_documents([t]) for t in texts])
    np.testing.assert_allclose(batched, single, atol=1e-4)


def test_reranker_orders_and_scores():
    r = Reranker()
    passages = [f"passage {i} about topic {i % 3}" for i in range(10)]
    ranked = r.rerank("what is topic 1", passages, top_n=4)
    assert len(ranked) == 4
    scores = [s for _, s in ranked]
    assert scores == sorted(scores, reverse=True)
    # scoring must be batch-size invariant
    s_all = r.score("q", passages)
    s_two = np.concatenate([r.score("q", passages[:6]), r.score("q", passages[6:])])
    np.testing.assert_allclose(s_all, s_two, atol=1e-4)
    assert r.rerank("q", [], top_n=4) == []


# ------------------------------------------------------------------- store

def _random_embeddings(n, dim, seed=0):
    rng = np.random.default_rng(seed)
    v = rng.normal(size=(n, dim)).astype(np.float32)
    return v / np.linalg.norm(v, axis=1, keepdims=True)


def test_vector_store_exact_search_and_threshold():
    dim = 16
    store = VectorStore(dim=dim)
    emb = _random_embeddings(50, dim)
    docs = [Document(content=f"doc{i}", metadata={"source": f"f{i % 5}.txt"})
            for i in range(50)]
    store.add(docs, emb)
    hits = store.search(emb[7], top_k=3)
    assert hits[0][0].content == "doc7"
    assert hits[0][1] > 0.99  # self-match relevance ≈ 1
    # threshold filters
    assert store.search(emb[7], top_k=3, score_threshold=1.1) == []


def test_vector_store_delete_and_sources():
    dim = 8
    store = VectorStore(dim=dim)
    emb = _random_embeddings(20, dim)
    docs = [Document(content=f"d{i}", metadata={"source": f"s{i % 2}.pdf"})
            for i in range(20)]
    store.add(docs, emb)
    assert sorted(store.list_sources()) == ["s0.pdf", "s1.pdf"]
    removed = store.delete_by_source(["s0.pdf"])
    assert removed == 10
    assert len(store) == 10
    hits = store.search(emb[0], top_k=20)
    assert all(h[0].metadata["source"] == "s1.pdf" for h in hits)


def test_vector_store_growth_past_capacity():
    dim = 8
    store = VectorStore(dim=dim)
    emb = _random_embeddings(600, dim)  # > initial 256 capacity
    docs = [Document(content=f"d{i}") for i in range(600)]
    store.add(docs[:100], emb[:100])
    store.add(docs[100:], emb[100:])
    hits = store.search(emb[450], top_k=1)
    assert hits[0][0].content == "d450"


def test_ivf_matches_exact_for_easy_queries():
    dim = 32
    n = 1024
    emb = _random_embeddings(n, dim, seed=3)
    exact = VectorStore(dim=dim, index_type="exact")
    ivf = VectorStore(dim=dim, index_type="ivf", nlist=16, nprobe=8)
    docs = [Document(content=f"d{i}") for i in range(n)]
    exact.add(docs, emb)
    ivf.add([Document(content=f"d{i}") for i in range(n)], emb)
    agree = 0
    for q in range(0, 100, 10):
        e_top = exact.search(emb[q], top_k=1)[0][0].content
        i_top = ivf.search(emb[q], top_k=1)
        if i_top and i_top[0][0].content == e_top:
            agree += 1
    assert agree >= 8  # self-queries: probed cell contains the vector


def test_ivf_incremental_add_and_delete_mask():
    """Adds after the first build assign to existing centroids (no retrain
    below the 2x threshold) and are findable; deleted rows never surface
    even without a rebuild."""
    dim = 32
    ivf = VectorStore(dim=dim, index_type="ivf", nlist=8, nprobe=8)
    emb = _random_embeddings(512, dim, seed=5)
    ivf.add([Document(content=f"a{i}", metadata={"source": "a.txt"})
             for i in range(512)], emb)
    ivf.search(emb[0], top_k=1)          # triggers training build
    trained_n = ivf._ivf_trained_n
    extra = _random_embeddings(100, dim, seed=6)
    ivf.add([Document(content=f"b{i}", metadata={"source": "b.txt"})
             for i in range(100)], extra)
    hits = ivf.search(extra[42], top_k=1)
    assert hits and hits[0][0].content == "b42"
    assert ivf._ivf_trained_n == trained_n  # assign-only, no retrain
    ivf.delete_by_source(["b.txt"])
    hits = ivf.search(extra[42], top_k=5)
    assert all(h[0].metadata["source"] == "a.txt" for h in hits)


# ----------------------------------------------------------- bm25/splitter

def test_bm25_ranks_matching_docs():
    idx = BM25Index()
    idx.add(["the cat sat on the mat", "dogs chase cats in the yard",
             "quantum computing with superconducting qubits"])
    hits = idx.search("quantum qubits", top_k=2)
    assert hits and hits[0][0] == 2


def test_rrf_fuses_rankings():
    fused = reciprocal_rank_fusion([[1, 2, 3], [3, 1, 9]], top_k=2)
    assert fused[0] == 1 or fused[0] == 3
    assert len(fused) == 2


def test_splitter_chunk_and_overlap():
    sp = TokenTextSplitter(chunk_size=50, chunk_overlap=10)
    text = " ".join(f"word{i}" for i in range(100)) + ".\n\n" + \
           " ".join(f"tail{i}" for i in range(50)) + "."
    chunks = sp.split(text)
    assert len(chunks) >= 2
    for c in chunks:
        assert len(sp.tokenizer.encode(c)) <= 60  # size + boundary slack
    assert sp.split("") == []
    assert sp.split("tiny") == ["tiny"]
    with pytest.raises(ValueError):
        TokenTextSplitter(chunk_size=10, chunk_overlap=10)
