"""Sequence-parallel long-context prefill (engine.prefill_long /
kv_cache.prefill_seq_parallel / llama.prefill_seq_parallel): ring attention
over mesh["seq"] fills the paged pool in one pass, and the subsequent
paged decode matches the dense model exactly — §5.7 as a serving
capability, not just a library."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from generativeaiexamples_tpu.core.config import EngineConfig
from generativeaiexamples_tpu.engine.engine import EngineCore
from generativeaiexamples_tpu.engine.tokenizer import ByteTokenizer
from generativeaiexamples_tpu.models import llama
from generativeaiexamples_tpu.parallel import mesh as pmesh


@pytest.fixture(scope="module")
def longctx():
    cfg = llama.LlamaConfig.tiny(vocab_size=300)
    params = llama.init_params(jax.random.PRNGKey(5), cfg)
    mesh = pmesh.create_mesh(
        pmesh.MeshConfig(axes=pmesh.LONGCTX_AXES, shape=(1, 4, 2)))
    ecfg = EngineConfig(max_batch_size=2, max_seq_len=256, page_size=16,
                        prefill_chunk=32, spec_decode="off")
    core = EngineCore(cfg, ecfg, params, eos_id=ByteTokenizer().eos_id,
                      mesh=mesh)
    return cfg, params, core


def test_prefill_seq_parallel_logits_and_kv_match_dense(longctx):
    cfg, params, core = longctx
    rng = np.random.default_rng(0)
    n = 100
    toks = rng.integers(3, 290, size=(1, n)).astype(np.int32)
    # pad to lcm(page, seq) alignment like the engine does
    S = 112                                      # lcm(16, 4) = 16 → 112 ≥ 100
    padded = np.zeros((1, S), np.int32)
    padded[0, :n] = toks

    dense = llama.forward(params, cfg, jnp.asarray(toks))
    logits, k_stack, v_stack = llama.prefill_seq_parallel(
        params, cfg, jnp.asarray(padded), core.mesh,
        seq_lens=jnp.asarray([n], jnp.int32))
    np.testing.assert_allclose(np.asarray(logits[0]),
                               np.asarray(dense[0, -1]),
                               atol=2e-4, rtol=2e-4)
    assert k_stack.shape == (cfg.n_layers, 1, S, cfg.n_kv_heads,
                             cfg.head_dim)


def test_engine_prefill_long_then_decode_matches_dense(longctx):
    """prefill_long → sample → activate → paged decode must reproduce the
    dense model's greedy continuation (the full serving loop for a prompt
    processed in ONE sequence-parallel pass)."""
    cfg, params, core = longctx
    assert core.supports_long_prefill
    rng = np.random.default_rng(1)
    prompt = list(rng.integers(3, 290, size=120))

    seq = list(prompt)
    for _ in range(6):
        logits = llama.forward(params, cfg, jnp.asarray([seq], jnp.int32))
        seq.append(int(jnp.argmax(logits[0, -1])))
    expect = seq[len(prompt):]

    state = core.init_state()
    alloc = core.new_allocator()
    table = np.zeros((core.batch, core.max_pages_per_slot), np.int32)
    pages = alloc.alloc(core.pages_for(len(prompt)))
    table[0, :len(pages)] = pages
    state, logits = core.prefill_long(state, prompt, table[0], slot=0)
    first = core.sample(logits, jax.random.PRNGKey(0), 0.0, 0, 1.0)
    state = core.activate(state, 0, first, generated=1, max_gen=6,
                          temperature=0.0, top_k=0, top_p=1.0)
    got = [first]
    for _ in range(5):
        state, out = core.decode(state, core.put_table(table))
        assert bool(out["emitted"][0, 0])
        got.append(int(out["sampled"][0, 0]))
    assert got == expect


def test_scheduler_routes_long_prompts_through_ring_prefill(longctx):
    """On a LONGCTX mesh the scheduler's admission takes the one-pass
    sequence-parallel route for multi-chunk prompts, and the streamed
    output still matches the dense model's greedy continuation."""
    from generativeaiexamples_tpu.core.metrics import REGISTRY
    from generativeaiexamples_tpu.engine.scheduler import Request, Scheduler
    from generativeaiexamples_tpu.engine.tokenizer import ByteTokenizer

    cfg, params, core = longctx
    tok = ByteTokenizer()
    prompt = tok.encode("long context serving over the ring " * 4,
                        add_bos=True)
    assert len(prompt) > core.chunk        # multi-chunk → long route

    seq = list(prompt)
    for _ in range(6):
        logits = llama.forward(params, cfg, jnp.asarray([seq], jnp.int32))
        seq.append(int(jnp.argmax(logits[0, -1])))
    expect = tok.decode(seq[len(prompt):])

    before = REGISTRY.counter("prefill_long_passes").value
    sched = Scheduler(core, tok)
    req = Request(prompt_ids=list(prompt), max_tokens=6, temperature=0.0)
    sched.submit(req)
    while sched._tick():
        pass
    assert req.error is None
    assert REGISTRY.counter("prefill_long_passes").value == before + 1
    parts = []
    while not req.out_queue.empty():
        item = req.out_queue.get_nowait()
        if isinstance(item, str):
            parts.append(item)
    assert "".join(parts) == expect


def test_prefill_long_requires_seq_axis():
    cfg = llama.LlamaConfig.tiny(vocab_size=300)
    params = llama.init_params(jax.random.PRNGKey(5), cfg)
    core = EngineCore(cfg, EngineConfig(max_batch_size=2, max_seq_len=128,
                                        page_size=16, prefill_chunk=32),
                      params, eos_id=2)
    assert not core.supports_long_prefill
    with pytest.raises(ValueError, match="seq"):
        core.prefill_long(core.init_state(), [1, 2, 3],
                          np.zeros(8, np.int32), 0)