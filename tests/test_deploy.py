"""Supervisor tests: health-gated ordering, crash restart with backoff,
ordered teardown — SURVEY §5.3 failure detection/recovery + compose-parity
(VERDICT round-1 missing items #6/#7 done-criteria). Services are tiny
python HTTP servers so the tests run in seconds."""

import socket
import sys
import textwrap
import time

import pytest
import requests

from generativeaiexamples_tpu.core.metrics import REGISTRY
from generativeaiexamples_tpu.deploy import supervisor as supervisor_mod
from generativeaiexamples_tpu.deploy.supervisor import ServiceSpec, Supervisor


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _http_service(port: int, delay: float = 0.0, die_after: float = 0.0,
                  marker_file: str = "") -> list:
    """Command for a toy /health HTTP service (optionally slow to start or
    self-crashing once a marker file does not yet exist)."""
    code = textwrap.dedent(f"""
        import http.server, os, sys, threading, time
        time.sleep({delay})
        marker = {marker_file!r}
        if marker and not os.path.exists(marker):
            open(marker, "w").write("crashed once")
            sys.exit(3)
        class H(http.server.BaseHTTPRequestHandler):
            def do_GET(self):
                self.send_response(200 if self.path == "/health" else 404)
                self.end_headers()
                self.wfile.write(b'ok')
            def log_message(self, *a):
                pass
        http.server.HTTPServer(("127.0.0.1", {port}), H).serve_forever()
    """)
    return [sys.executable, "-c", code]


def test_health_gated_ordering_and_teardown():
    """B (depends on A) must not start until A is healthy; down() stops
    both."""
    pa, pb = _free_port(), _free_port()
    sup = Supervisor([
        ServiceSpec(name="a", command=_http_service(pa, delay=1.0),
                    health_url=f"http://127.0.0.1:{pa}/health",
                    startup_timeout_s=30),
        ServiceSpec(name="b", command=_http_service(pb),
                    health_url=f"http://127.0.0.1:{pb}/health",
                    depends_on=["a"], startup_timeout_s=30),
    ], poll_interval_s=0.1)
    t0 = time.monotonic()
    sup.up()
    try:
        assert time.monotonic() - t0 >= 1.0   # gated on A's slow start
        st = sup.status()
        assert st["a"]["healthy"] and st["b"]["healthy"]
        assert requests.get(f"http://127.0.0.1:{pb}/health",
                            timeout=5).status_code == 200
    finally:
        sup.down()
    st = sup.status()
    assert not st["a"]["alive"] and not st["b"]["alive"]


def test_crash_restart_with_backoff(tmp_path):
    """A service that dies once is detected and restarted; the restart
    counter records the recovery."""
    port = _free_port()
    marker = str(tmp_path / "crashed")
    spec = ServiceSpec(name="flaky",
                       command=_http_service(port, marker_file=marker),
                       health_url=f"http://127.0.0.1:{port}/health",
                       startup_timeout_s=30)
    sup = Supervisor([spec], poll_interval_s=0.1)
    # first run exits rc=3 before ever serving → up() reports it loudly
    with pytest.raises(RuntimeError, match="exited"):
        sup.up()
    # second run (marker exists) serves; crash it mid-flight and watch the
    # monitor bring it back
    sup2 = Supervisor([spec], poll_interval_s=0.1)
    sup2.up()
    try:
        pid = sup2.status()["flaky"]["pid"]
        import os
        import signal as sig
        os.kill(pid, sig.SIGKILL)
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            st = sup2.status()["flaky"]
            if st["alive"] and st["restarts"] == 1:
                break
            time.sleep(0.2)
        st = sup2.status()["flaky"]
        assert st["restarts"] == 1 and st["alive"]
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            if sup2.status()["flaky"]["healthy"]:
                break
            time.sleep(0.2)
        assert sup2.status()["flaky"]["healthy"]
    finally:
        sup2.down()


def test_restart_backoff_is_jittered_and_counted(monkeypatch):
    """The restart path routes through the SHARED full-jitter backoff
    (server/resilience.py — no more synchronized min(2**n, 60) herd) and
    counts supervisor_restarts_total{service}."""
    delays = []

    def fake_backoff(attempt, base_s=1.0, cap_s=60.0, rng=None):
        delays.append((attempt, base_s, cap_s))
        return 0.0                      # restart immediately: fast test

    monkeypatch.setattr(supervisor_mod, "full_jitter_backoff", fake_backoff)
    spec = ServiceSpec(name="dying",
                       command=[sys.executable, "-c",
                                "import sys; sys.exit(1)"],
                       max_restarts=2)
    restarts0 = REGISTRY.counter("supervisor_restarts_total",
                                 labels={"service": "dying"}).value
    sup = Supervisor([spec], poll_interval_s=0.05)
    sup.up()
    try:
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if sup.status()["dying"]["restarts"] == 2:
                break
            time.sleep(0.05)
        assert sup.status()["dying"]["restarts"] == 2
    finally:
        sup.down()
    # full jitter consulted once per restart, with growing attempt numbers
    assert [a for a, _, _ in delays] == [2, 3]
    assert all(cap == 60.0 for _, _, cap in delays)
    assert REGISTRY.counter("supervisor_restarts_total",
                            labels={"service": "dying"}).value \
        == restarts0 + 2


def test_dependency_cycle_rejected():
    with pytest.raises(ValueError, match="cycle"):
        Supervisor([
            ServiceSpec(name="x", command=["true"], depends_on=["y"]),
            ServiceSpec(name="y", command=["true"], depends_on=["x"]),
        ])


def test_unknown_dependency_rejected():
    with pytest.raises(ValueError, match="unknown dependency"):
        Supervisor([ServiceSpec(name="x", command=["true"],
                                depends_on=["ghost"])])