// Byte-level BPE tokenizer core — the in-tree native replacement for the
// Rust `tokenizers` runtime the reference ships inside its model containers
// (HF tokenizers is the NIM images' host-side hot path; ref
// docs/architecture.md:49-61 keeps it out of the Python tree entirely).
//
// Split of labor (see engine/native_tokenizer.py):
//   * Python (cold path): parses tokenizer.json, inverts the GPT-2
//     byte<->unicode table so every vocab entry arrives here as RAW BYTES,
//     resolves each merge rule to ids — (left_id, right_id) -> merged_id —
//     detects the pre-tokenization pattern, and builds Unicode letter/number
//     bitsets from unicodedata.
//   * C++ (hot path): UTF-8 scan, pre-tokenization, and the BPE merge loop
//     over int32 id sequences (no string hashing at encode time: merges are
//     pure id-pair lookups in one flat hash map).
//
// Two pre-tokenization modes, selected at create time:
//   mode 0 — GPT-2:
//     's|'t|'re|'ve|'m|'ll|'d| ?\p{L}+| ?\p{N}+| ?[^\s\p{L}\p{N}]+
//     |\s+(?!\S)|\s+
//   mode 1 — Llama-3:
//     (?i:'s|'t|'re|'ve|'m|'ll|'d)|[^\r\n\p{L}\p{N}]?\p{L}+|\p{N}{1,3}
//     | ?[^\s\p{L}\p{N}]+[\r\n]*|\s*[\r\n]+|\s+(?!\S)|\s+
// with \p{L}/\p{N} answered by caller-supplied bitsets, so the scanner has
// no Unicode tables of its own and stays dependency-free.
//
// The merge loop is the standard heap + doubly-linked-list algorithm
// (O(n log n) per piece): pieces are NOT bounded — a long '=====' divider
// or a minified blob forms one piece, and a quadratic scan there would
// block the ingest thread for minutes on adversarial documents.
//
// Thread-safety: a handle is immutable after bpe_create; encode/decode may
// run concurrently from any number of threads.

#include <cstdint>
#include <cstring>
#include <queue>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

struct Bpe {
  // id -> raw bytes (already unmapped from the GPT-2 unicode alphabet)
  std::vector<std::string> tokens;
  // (left_id << 32 | right_id) -> (rank << 32 | merged_id)
  std::unordered_map<uint64_t, uint64_t> merges;
  int32_t byte_init[256];      // byte value -> initial token id
  std::vector<uint8_t> letter_bits, number_bits;  // 1 bit per codepoint
  uint32_t max_cp = 0;
  int mode = 0;                // 0 = gpt2, 1 = llama3

  bool is_class(const std::vector<uint8_t>& bits, uint32_t cp) const {
    return cp < max_cp && (bits[cp >> 3] >> (cp & 7)) & 1;
  }
  bool is_letter(uint32_t cp) const { return is_class(letter_bits, cp); }
  bool is_number(uint32_t cp) const { return is_class(number_bits, cp); }
};

// Decode one UTF-8 codepoint at s[i]; advances i. Invalid bytes decode as
// themselves (latin-1 style) so the scanner never stalls on binary input —
// the byte-level alphabet can represent anything.
inline uint32_t next_cp(const uint8_t* s, int len, int& i) {
  uint8_t b = s[i];
  if (b < 0x80) { i += 1; return b; }
  int n = (b >= 0xF0) ? 4 : (b >= 0xE0) ? 3 : (b >= 0xC0) ? 2 : 1;
  if (n == 1 || i + n > len) { i += 1; return b; }
  uint32_t cp = b & (0xFF >> (n + 1));
  for (int k = 1; k < n; ++k) {
    uint8_t c = s[i + k];
    if ((c & 0xC0) != 0x80) { i += 1; return b; }
    cp = (cp << 6) | (c & 0x3F);
  }
  i += n;
  return cp;
}

inline bool is_ws(uint32_t cp) {
  // Rust char::is_whitespace / \s in the tokenizers regex crates:
  // Unicode White_Space property.
  switch (cp) {
    case 0x09: case 0x0A: case 0x0B: case 0x0C: case 0x0D: case 0x20:
    case 0x85: case 0xA0: case 0x1680: case 0x2028: case 0x2029: case 0x202F:
    case 0x205F: case 0x3000:
      return true;
    default:
      return cp >= 0x2000 && cp <= 0x200A;
  }
}

inline bool is_crlf(uint32_t cp) { return cp == '\r' || cp == '\n'; }

struct Piece { int start, end; };  // byte offsets [start, end)

// Try a contraction at s[i] ('s 't 're 've 'm 'll 'd); case-insensitive in
// llama3 mode. Returns byte length (0 = no match).
inline int match_contraction(const uint8_t* s, int len, int i, bool ci) {
  if (s[i] != '\'' || i + 1 >= len) return 0;
  uint8_t a = s[i + 1], b = (i + 2 < len) ? s[i + 2] : 0;
  if (ci) { a |= 0x20; b |= 0x20; }   // ASCII lowercase
  if (a == 's' || a == 't' || a == 'm' || a == 'd') return 2;
  if ((a == 'r' && b == 'e') || (a == 'v' && b == 'e') ||
      (a == 'l' && b == 'l'))
    return 3;
  return 0;
}

// Pre-tokenization over raw bytes. Mirrors the regex alternation order of
// the selected mode; produces byte-offset pieces BPE merges never cross.
void pre_tokenize(const Bpe& bpe, const uint8_t* s, int len,
                  std::vector<Piece>& out) {
  const bool llama = bpe.mode == 1;
  int i = 0;
  while (i < len) {
    int start = i;
    int n = match_contraction(s, len, i, /*ci=*/llama);
    if (n) { out.push_back({start, start + n}); i = start + n; continue; }

    int j = i;
    uint32_t cp = next_cp(s, len, j);

    if (llama) {
      // --- "[^\r\n\p{L}\p{N}]?\p{L}+" --------------------------------
      // optional single leading char that is not CR/LF/letter/number
      {
        int jl = j;
        uint32_t head = cp;
        bool consumed_head = false;
        if (!is_crlf(head) && !bpe.is_letter(head) && !bpe.is_number(head) &&
            jl < len) {
          int k = jl;
          uint32_t c2 = next_cp(s, len, k);
          if (bpe.is_letter(c2)) { consumed_head = true; jl = k; }
        }
        if (bpe.is_letter(head) || consumed_head) {
          while (jl < len) {
            int k = jl;
            uint32_t c = next_cp(s, len, k);
            if (!bpe.is_letter(c)) break;
            jl = k;
          }
          out.push_back({start, jl}); i = jl; continue;
        }
      }
      // --- "\p{N}{1,3}" ----------------------------------------------
      if (bpe.is_number(cp)) {
        int cnt = 1, jn = j;
        while (jn < len && cnt < 3) {
          int k = jn;
          uint32_t c = next_cp(s, len, k);
          if (!bpe.is_number(c)) break;
          jn = k; ++cnt;
        }
        out.push_back({start, jn}); i = jn; continue;
      }
      // --- " ?[^\s\p{L}\p{N}]+[\r\n]*" -------------------------------
      {
        int jp = j;
        uint32_t c0 = cp;
        if (c0 == ' ' && jp < len) {
          int k = jp;
          uint32_t c2 = next_cp(s, len, k);
          if (!is_ws(c2) && !bpe.is_letter(c2) && !bpe.is_number(c2)) {
            c0 = c2; jp = k;
          }
        }
        if (!is_ws(c0) && !bpe.is_letter(c0) && !bpe.is_number(c0)) {
          while (jp < len) {
            int k = jp;
            uint32_t c = next_cp(s, len, k);
            if (is_ws(c) || bpe.is_letter(c) || bpe.is_number(c)) break;
            jp = k;
          }
          while (jp < len && is_crlf(s[jp])) ++jp;   // trailing newlines
          out.push_back({start, jp}); i = jp; continue;
        }
      }
      // --- "\s*[\r\n]+" ----------------------------------------------
      if (is_ws(cp)) {
        // greedy \s* then require >=1 CR/LF, with backtracking: find the
        // last CR/LF inside the maximal \s run reachable from here.
        int run_end = j, last_nl_end = is_crlf(cp) ? j : -1;
        while (run_end < len) {
          int k = run_end;
          uint32_t c = next_cp(s, len, k);
          if (!is_ws(c)) break;
          run_end = k;
          if (is_crlf(c)) last_nl_end = k;
        }
        if (last_nl_end > 0) {
          // trailing [\r\n]+ extends to the last newline in the run; any
          // ws after it belongs to the next alternative's turn
          out.push_back({start, last_nl_end}); i = last_nl_end; continue;
        }
        // fall through to the shared \s+(?!\S)|\s+ handling below, reusing
        // the scan: no newline in the run
        int end = run_end;
        if (run_end < len) {
          // non-space follows: back off one codepoint (the (?!\S))
          // find start of the run's final codepoint
          int prev = start, scan = start;
          while (scan < run_end) { prev = scan; next_cp(s, len, scan); }
          if (prev > start) end = prev;
        }
        out.push_back({start, end});
        i = end;
        continue;
      }
      // unreachable: every codepoint class is covered above
      out.push_back({start, j}); i = j; continue;
    }

    // ------------------------- GPT-2 mode ------------------------------
    // optional single leading space for letter/number/punct alternatives
    if (cp == ' ' && j < len) {
      int j2 = j;
      uint32_t cp2 = next_cp(s, len, j2);
      if (!is_ws(cp2)) { cp = cp2; i = j; j = j2; }
    }
    if (bpe.is_letter(cp)) {                       // " ?\p{L}+"
      while (j < len) {
        int k = j;
        uint32_t c = next_cp(s, len, k);
        if (!bpe.is_letter(c)) break;
        j = k;
      }
      out.push_back({start, j}); i = j; continue;
    }
    if (bpe.is_number(cp)) {                       // " ?\p{N}+"
      while (j < len) {
        int k = j;
        uint32_t c = next_cp(s, len, k);
        if (!bpe.is_number(c)) break;
        j = k;
      }
      out.push_back({start, j}); i = j; continue;
    }
    if (!is_ws(cp)) {                              // " ?[^\s\p{L}\p{N}]+"
      while (j < len) {
        int k = j;
        uint32_t c = next_cp(s, len, k);
        if (is_ws(c) || bpe.is_letter(c) || bpe.is_number(c)) break;
        j = k;
      }
      out.push_back({start, j}); i = j; continue;
    }
    // --- whitespace: "\s+(?!\S)" then "\s+" -----------------------------
    // Greedy run with lookahead backoff: if a non-space follows the run,
    // the (?!\S) lookahead forces backing off exactly one codepoint, which
    // then either prefixes the next piece (a plain space feeds the " ?X"
    // alternatives) or, for any other whitespace char, matches "\s+" alone
    // on the next scanner iteration. A single ' ' before a non-space never
    // reaches here — the " ?X" alternatives above are exhaustive over
    // non-space codepoints and have already absorbed it.
    int run_end = j;        // end of the ws run (j is past the first ws cp)
    int last_ws = start;    // start offset of the run's final ws codepoint
    while (run_end < len) {
      int k = run_end;
      uint32_t c = next_cp(s, len, k);
      if (!is_ws(c)) break;
      last_ws = run_end;
      run_end = k;
    }
    int end = run_end;
    if (run_end < len && last_ws > start)
      end = last_ws;        // non-space follows: back off one codepoint
    out.push_back({start, end});
    i = end;
  }
}

// BPE merge loop for one piece: heap + doubly-linked list, O(n log n).
// Heap entries are validated lazily (stale pairs — whose endpoints were
// consumed by an earlier merge — are skipped on pop).
struct HeapEntry {
  uint32_t rank;
  int32_t pos;               // left index of the pair
  int32_t left_id, right_id; // ids at push time (staleness check)
  bool operator>(const HeapEntry& o) const {
    return rank != o.rank ? rank > o.rank : pos > o.pos;
  }
};

void merge_piece(const Bpe& bpe, std::vector<int32_t>& ids) {
  const int n = (int)ids.size();
  if (n < 2) return;
  std::vector<int32_t> prev(n), next(n);
  for (int k = 0; k < n; ++k) { prev[k] = k - 1; next[k] = k + 1; }
  std::priority_queue<HeapEntry, std::vector<HeapEntry>,
                      std::greater<HeapEntry>> heap;
  auto push_pair = [&](int l, int r) {
    uint64_t key = (uint64_t)(uint32_t)ids[l] << 32 | (uint32_t)ids[r];
    auto it = bpe.merges.find(key);
    if (it != bpe.merges.end())
      heap.push({(uint32_t)(it->second >> 32), l, ids[l], ids[r]});
  };
  for (int k = 0; k + 1 < n; ++k) push_pair(k, k + 1);

  std::vector<uint8_t> dead(n, 0);
  while (!heap.empty()) {
    HeapEntry e = heap.top();
    heap.pop();
    int l = e.pos;
    if (dead[l] || ids[l] != e.left_id) continue;
    int r = next[l];
    if (r >= n || ids[r] != e.right_id) continue;
    uint64_t key = (uint64_t)(uint32_t)ids[l] << 32 | (uint32_t)ids[r];
    auto it = bpe.merges.find(key);
    if (it == bpe.merges.end() || (uint32_t)(it->second >> 32) != e.rank)
      continue;
    // merge r into l
    ids[l] = (int32_t)(it->second & 0xFFFFFFFFu);
    dead[r] = 1;
    next[l] = next[r];
    if (next[r] < n) prev[next[r]] = l;
    if (prev[l] >= 0) push_pair(prev[l], l);
    if (next[l] < n) push_pair(l, next[l]);
  }
  int out = 0;
  for (int k = 0; k < n; k = next[k]) ids[out++] = ids[k];
  ids.resize(out);
}

}  // namespace

extern "C" {

void* bpe_create(int32_t n_tokens, const int32_t* tok_lens,
                 const uint8_t* tok_bytes, int32_t n_merges,
                 const uint64_t* merge_keys, const int32_t* merge_merged,
                 const int32_t* byte_init, const uint8_t* letter_bits,
                 const uint8_t* number_bits, int32_t bits_len,
                 int32_t mode) {
  Bpe* b = new Bpe();
  b->tokens.reserve(n_tokens);
  const uint8_t* p = tok_bytes;
  for (int32_t t = 0; t < n_tokens; ++t) {
    b->tokens.emplace_back(reinterpret_cast<const char*>(p), tok_lens[t]);
    p += tok_lens[t];
  }
  b->merges.reserve((size_t)n_merges * 2);
  for (int32_t m = 0; m < n_merges; ++m)
    b->merges[merge_keys[m]] =
        (uint64_t)(uint32_t)m << 32 | (uint32_t)merge_merged[m];
  std::memcpy(b->byte_init, byte_init, 256 * sizeof(int32_t));
  b->letter_bits.assign(letter_bits, letter_bits + bits_len);
  b->number_bits.assign(number_bits, number_bits + bits_len);
  b->max_cp = (uint32_t)bits_len * 8;
  b->mode = mode;
  return b;
}

void bpe_free(void* h) { delete static_cast<Bpe*>(h); }

// Encode utf8[0..len) -> out (capacity out_cap). Returns the number of ids
// produced; if it exceeds out_cap, nothing past out_cap is written and the
// required count is returned (caller re-calls with a bigger buffer).
int32_t bpe_encode(const void* h, const uint8_t* utf8, int32_t len,
                   int32_t* out, int32_t out_cap) {
  const Bpe& bpe = *static_cast<const Bpe*>(h);
  std::vector<Piece> pieces;
  pieces.reserve(len / 4 + 4);
  pre_tokenize(bpe, utf8, len, pieces);
  int32_t n = 0;
  std::vector<int32_t> ids;
  for (const Piece& pc : pieces) {
    ids.clear();
    for (int k = pc.start; k < pc.end; ++k)
      ids.push_back(bpe.byte_init[utf8[k]]);
    merge_piece(bpe, ids);
    for (int32_t id : ids) {
      if (n < out_cap) out[n] = id;
      ++n;
    }
  }
  return n;
}

// Decode ids -> raw bytes. Returns byte count (same overflow contract).
int32_t bpe_decode(const void* h, const int32_t* ids, int32_t n_ids,
                   uint8_t* out, int32_t out_cap) {
  const Bpe& bpe = *static_cast<const Bpe*>(h);
  int32_t n = 0;
  for (int32_t k = 0; k < n_ids; ++k) {
    int32_t id = ids[k];
    if (id < 0 || (size_t)id >= bpe.tokens.size()) continue;
    const std::string& t = bpe.tokens[id];
    for (char c : t) {
      if (n < out_cap) out[n] = (uint8_t)c;
      ++n;
    }
  }
  return n;
}

}  // extern "C"
